"""Expert parallelism built on the ``alltoall`` building block.

The reference names ``alltoall`` as its expert-dispatch primitive
(SURVEY §2.4 "Ulysses-style sequence parallel / EP dispatch building
block", alltoall.py:35-74 there).  This module composes it into the
standard MoE data path: tokens bucketed by destination expert, one
``alltoall`` to deliver each expert its work, expert computation local,
and the inverse ``alltoall`` + unsort to put results back in token
order.  Differentiable end to end (``alltoall`` transposes to itself
with the inverse layout).

Capacity model: fixed capacity per (source rank, expert) of
``tokens // n_experts`` — the capacity-factor-1.0 regime.  Callers pad
or drop to balanced assignments first (static shapes are what make the
dispatch one fused ICI collective instead of a host gather).
"""

import jax
import jax.numpy as jnp
from jax import lax

from mpi4jax_tpu.ops._core import as_token
from mpi4jax_tpu.ops.collectives import alltoall, alltoall_multi

__all__ = [
    "expert_dispatch",
    "expert_combine",
    "default_capacity",
    "topk_route",
    "topk_moe",
    "load_balancing_loss",
    "router_z_loss",
    "dropped_fraction",
]


def load_balancing_loss(probs, k=1):
    """Switch/GShard auxiliary load-balancing loss, generalised top-k.

    ``E * Σ_e f_e · P_e`` where ``f_e`` is the fraction of the ``T*k``
    routing assignments that chose expert ``e`` (pre-capacity — drops
    don't change what the router *wanted*) and ``P_e`` the mean router
    probability of ``e`` (Switch Transformer eq. 4, arXiv:2101.03961;
    GShard arXiv:2006.16668).  Equal to 1 at perfect balance, up to
    ``E`` at full collapse.  The gradient flows through ``P`` only
    (``f`` is discrete) — the standard estimator.

    Args:
      probs: ``(T, E)`` post-softmax router probabilities.
      k: experts per token the router selects.
    """
    t, n_experts = probs.shape
    _, top = lax.top_k(probs, k)
    chosen = jnp.zeros((t, n_experts), probs.dtype)
    chosen = chosen.at[jnp.arange(t)[:, None], top].set(1.0)
    f = lax.stop_gradient(chosen.sum(0) / (t * k))
    return n_experts * jnp.sum(f * probs.mean(0))


def router_z_loss(logits):
    """Router z-loss (ST-MoE, arXiv:2202.08906 eq. 5): mean squared
    ``logsumexp`` of the router logits.  Keeps logits small so the
    softmax stays in its well-conditioned range; typical weight 1e-3.

    Args:
      logits: ``(T, E)`` pre-softmax router logits.
    """
    z = jax.nn.logsumexp(logits, axis=-1)
    return jnp.mean(jnp.square(z))


def dropped_fraction(valid, n_tokens, k=1):
    """Fraction of the ``n_tokens * k`` routing assignments that
    overflowed expert capacity (``valid`` as returned by
    :func:`topk_route`).  0 = nothing dropped."""
    kept = valid.sum()
    return 1.0 - kept / (n_tokens * k)


def expert_dispatch(x, expert_idx, comm, *, token=None):
    """Route tokens to experts (expert e = rank e of ``comm``).

    Must be called inside the comm's ``shard_map``.

    Args:
      x: ``(T, d)`` local tokens; ``T`` must be divisible by
        ``comm.size``.
      expert_idx: ``(T,)`` int — destination expert per token. Must be
        **balanced**: exactly ``T // n_experts`` tokens per expert
        (capacity factor 1.0).
      comm: single-axis communicator; one expert per rank.

    Returns:
      ``(expert_input, order, token)`` where ``expert_input`` is
      ``(n_ranks, capacity, d)`` — this rank's expert's tokens, one
      capacity block per source rank — and ``order`` is the local sort
      permutation needed by :func:`expert_combine`.
    """
    token = as_token(token)
    n = comm.size
    t_local, d = x.shape
    if t_local % n:
        raise ValueError(
            f"token count {t_local} not divisible by {n} experts"
        )
    cap = t_local // n
    # stable bucket-by-expert; balancedness makes the reshape exact
    order = jnp.argsort(expert_idx, stable=True)
    buckets = x[order].reshape(n, cap, d)
    expert_input, token = alltoall(buckets, comm=comm, token=token)
    return expert_input, order, token


def default_capacity(k, tokens, n_experts):
    """Capacity-factor-1 default: ``ceil(k * tokens / n_experts)``."""
    return -(-k * tokens // n_experts)


def topk_route(scores, k, capacity):
    """Token-choice top-k routing with per-expert capacity (the
    GShard / Switch scheme, vs the expert-choice scheme of
    models/moe_transformer.py).

    Each token picks its ``k`` highest-scoring experts; each expert
    accepts at most ``capacity`` of the tokens that chose it, in score
    order — the rest overflow and are dropped (their combine
    contribution is zero; the residual connection carries them).  All
    shapes are static, so the result feeds one fused dispatch.

    Args:
      scores: ``(T, E)`` router scores — post-softmax probabilities in
        the usual case, but any non-NaN values work, including the
        raw-logits-with-``-inf``-masking idiom: slot validity is derived
        from how many tokens actually chose each expert, never from the
        score's finiteness, and a ``-inf``-scored choice gates to 0.
      k: experts per token.
      capacity: slots per expert.

    Returns ``(idx, gate, valid)``, each ``(E, capacity)``:
      ``idx[e, c]`` — source-token index of expert ``e``'s slot ``c``;
      ``gate[e, c]`` — that token's score for ``e``;
      ``valid[e, c]`` — False for unfilled / overflow slots.
    """
    t, n_experts = scores.shape
    # each token's chosen experts: (T, k)
    _, top_experts = lax.top_k(scores, k)
    chosen = jnp.zeros((t, n_experts), bool)
    chosen = chosen.at[jnp.arange(t)[:, None], top_experts].set(True)
    # sort key: the score where chosen (clamped finite so a legitimate
    # -inf-scored choice still outranks every non-chooser), -inf
    # elsewhere.  Each expert takes its top-capacity choosers by score.
    safe = jnp.maximum(scores, jnp.finfo(scores.dtype).min)
    key = jnp.where(chosen, safe, -jnp.inf)
    _, idx = lax.top_k(key.T, capacity)  # (E, cap)
    # validity = slot ordinal < chooser count (not score finiteness)
    count = jnp.minimum(chosen.sum(0), capacity)  # (E,)
    valid = jnp.arange(capacity)[None, :] < count[:, None]
    gate = jnp.take_along_axis(scores.T, idx, axis=1)
    gate = jnp.where(
        valid & jnp.isfinite(gate), gate, jnp.zeros((), gate.dtype)
    )
    return idx, gate, valid


def topk_moe(x, scores, expert_fn, comm, *, k=1, capacity=None, token=None,
             coalesce=None):
    """Full token-choice MoE layer: route → alltoall dispatch → expert
    compute → alltoall combine → gate-weighted scatter-add.

    The expert count is ``scores.shape[1]`` and must be a multiple of
    ``comm.size``: with ``E == comm.size`` (the classic layout) rank r
    hosts expert r and ``expert_fn(x_slot)`` maps the local expert's
    ``(n_src*capacity, d)`` buffer elementwise per token; with
    ``E == m*comm.size`` rank r hosts experts ``r*m .. r*m+m-1`` and
    ``expert_fn`` receives the stacked ``(m, n_src*capacity, d)`` local
    buffers.  Dropped (overflow) tokens contribute zero; tokens keep
    their gate weighting.  Differentiable end to end (the reference's
    alltoall building block; gates through the score gradient).

    Multi-expert dispatch is the canonical small-message path: each
    expert's per-peer slice is ``capacity*d`` elements, and the ``m``
    slices for one peer travel as ONE fused wire frame on the
    multi-process backend when they fit ``T4J_COALESCE_BYTES``
    (docs/performance.md "small-message coalescing"; ``coalesce``
    forces a side, results are bit-identical either way).

    ``capacity`` defaults to ``ceil(k * T / E)`` (capacity factor 1).
    Returns ``(y, token)`` with ``y`` shaped like ``x``.
    """
    token = as_token(token)
    n = comm.size
    t, d = x.shape
    if scores.ndim != 2 or scores.shape[0] != t or scores.shape[1] % n:
        raise ValueError(
            f"scores must be (tokens, n_experts) with n_experts a "
            f"multiple of comm.size={n} (tokens={t}), got "
            f"{scores.shape}"
        )
    n_experts = scores.shape[1]
    m = n_experts // n  # experts hosted per rank
    if capacity is None:
        capacity = default_capacity(k, t, n_experts)
    idx, gate, valid = topk_route(scores, k, capacity)
    buckets = x[idx] * valid[..., None].astype(x.dtype)  # (E, cap, d)
    # expert e = r*m + i lives on rank r as its local expert i: part i
    # stacks expert i of every rank -> (n, cap, d), one alltoall slice
    # per destination rank.  alltoall_multi fuses the m parts' slices
    # per peer into one frame on the wire tier.
    parts = [buckets[i::m] for i in range(m)]
    sent_parts, token = alltoall_multi(
        parts, comm=comm, token=token, coalesce=coalesce
    )
    if m == 1:
        # classic one-expert-per-rank contract: flat (n_src*cap, d)
        out = expert_fn(sent_parts[0].reshape(n * capacity, d))
        out_parts = [out.reshape(n, capacity, d)]
    else:
        stacked = jnp.stack(
            [s.reshape(n * capacity, d) for s in sent_parts]
        )  # (m, n_src*cap, d)
        out = expert_fn(stacked)
        out_parts = [out[i].reshape(n, capacity, d) for i in range(m)]
    back_parts, token = alltoall_multi(
        out_parts, comm=comm, token=token, coalesce=coalesce
    )
    # reassemble (E, cap, d): part i's row r is expert r*m+i's result
    if m == 1:
        vals = back_parts[0]
    else:
        vals = jnp.stack(back_parts, axis=1).reshape(
            n_experts, capacity, d
        )
    y = jnp.zeros_like(x).at[idx.reshape(-1)].add(
        (gate[..., None] * vals).reshape(-1, d)
    )
    return y, token


def expert_combine(expert_output, order, comm, *, token=None):
    """Inverse of :func:`expert_dispatch`: return results to their
    source ranks and original token order.

    ``expert_output``: ``(n_ranks, capacity, d)`` — the local expert's
    results, still grouped by source rank.
    """
    token = as_token(token)
    n, cap, d = expert_output.shape
    back, token = alltoall(expert_output, comm=comm, token=token)
    flat = back.reshape(n * cap, d)
    # O(T) permutation inverse (a second argsort would re-sort)
    inv = jnp.zeros_like(order).at[order].set(jnp.arange(order.shape[0]))
    return flat[inv], token
