"""Multi-host bootstrap: the pod-scale analog of "joining the MPI job".

The reference joins its distributed world by importing mpi4py at package
import (`mpi4jax/_src/__init__.py:3` -> MPI_Init); ranks and
communicators then come from the MPI runtime.  TPU pods use a different
world model: every host runs the same SPMD program, `jax.distributed`
glues the hosts' runtimes together, and the "world" is the global device
set of a `jax.sharding.Mesh` spanning all chips (ICI within a slice, DCN
across slices — XLA routes collectives over the right fabric).

Typical pod usage:

    import mpi4jax_tpu as m
    from mpi4jax_tpu.parallel import distributed

    distributed.initialize()          # no-op on a single host
    comm = distributed.world_comm()   # MeshComm over every chip in the job
    ...                               # shard_map + the 12 ops as usual

For MPMD jobs (divergent per-rank programs), use the proc backend /
launcher instead — that is the reference's one-process-per-rank model.
"""

import jax

from mpi4jax_tpu.parallel.comm import MeshComm, set_default_comm

__all__ = [
    "initialize",
    "world_mesh",
    "world_comm",
    "slice_mesh",
    "slice_comms",
    "two_tier_allreduce",
]


def initialize(**kwargs):
    """Connect this host to the distributed JAX runtime (idempotent).

    Thin wrapper over :func:`jax.distributed.initialize` (coordinator
    address / process count / process id are auto-detected on TPU pods,
    or passed through as keyword arguments).  Single-process sessions
    (no cluster env, no explicit arguments) are left untouched.
    """
    # NB: probe initialization state WITHOUT jax.process_count() — that
    # call initialises the XLA backend, after which
    # jax.distributed.initialize refuses to run
    if jax.distributed.is_initialized():
        return
    try:
        jax.distributed.initialize(**kwargs)
    except (ValueError, RuntimeError):
        if kwargs:
            raise
        # no coordinator/cluster detected: single-host session


def world_mesh(axes=None):
    """A mesh over every device in the job.

    ``axes``: optional ``(names, shape)`` tuple; default is one flat
    axis ``("world", n_global_devices)``.
    """
    devices = jax.devices()
    if axes is None:
        names, shape = ("world",), (len(devices),)
    else:
        names, shape = axes
        names = tuple(names)
        shape = tuple(shape)
    return jax.make_mesh(
        shape,
        names,
        axis_types=(jax.sharding.AxisType.Auto,) * len(names),
        devices=devices,
    )


def world_comm(axes=None, *, set_default=False):
    """MeshComm spanning the whole job (COMM_WORLD analog).

    With ``set_default=True`` it also becomes the ambient communicator
    used when ops get ``comm=None``.
    """
    comm = MeshComm.from_mesh(world_mesh(axes))
    if set_default:
        set_default_comm(comm)
    return comm


def _slice_index(device):
    idx = getattr(device, "slice_index", None)
    return 0 if idx is None else int(idx)


def slice_mesh():
    """A ``("slice", "chip")`` mesh making the ICI/DCN boundary explicit.

    On a multi-slice job, collectives over the ``chip`` axis ride ICI
    within each slice and collectives over the ``slice`` axis cross DCN
    — the fabric split of the reference's cross-node vs intra-node MPI
    (SURVEY §5.8: slice-local vs cross-slice subgroup detection).
    Single-slice (and CPU) jobs degenerate to shape ``(1, n)``.
    """
    import numpy as np

    devices = jax.devices()
    slices = sorted({_slice_index(d) for d in devices})
    by_slice = [
        sorted(
            (d for d in devices if _slice_index(d) == s),
            key=lambda d: d.id,
        )
        for s in slices
    ]
    if len({len(b) for b in by_slice}) != 1:
        raise ValueError(
            "slices have unequal chip counts: "
            f"{[len(b) for b in by_slice]}"
        )
    arr = np.array(by_slice, dtype=object)
    return jax.sharding.Mesh(
        arr,
        ("slice", "chip"),
        axis_types=(jax.sharding.AxisType.Auto,) * 2,
    )


def slice_comms():
    """(world, intra_slice, cross_slice) communicators on the slice mesh.

    ``intra_slice`` collectives run independently per slice over ICI;
    ``cross_slice`` collectives connect corresponding chips of every
    slice over DCN (the two-tier topology of SURVEY §5.8).
    """
    mesh = slice_mesh()
    world = MeshComm.from_mesh(mesh)
    return world, world.sub("chip"), world.sub("slice")


_slice_reducers = {}


def _slice_reducer(intra, op):
    """Memoised jitted intra-slice allreduce.  A fresh ``jax.jit`` per
    call would miss jax's C++ fast path (the wrapper's identity keys
    it) and RETRACE every invocation — measured 0.256 s/call at 32 MB
    before caching (VERDICT r4 weak #8).

    The key includes ``intra.mesh`` itself (MeshComm equality excludes
    it), so an equal comm built over a DIFFERENT mesh — other device
    order, backend reinit — gets its own compiled reduction instead of
    a stale one bound to the first mesh seen.  Entries are one jitted
    callable per distinct (mesh, comm, op) — a handful in any real
    program."""
    key = (intra.mesh, intra, op)
    fn = _slice_reducers.get(key)
    if fn is None:
        spec = jax.P(intra.axes)

        def local(v):
            from mpi4jax_tpu.ops.allreduce import allreduce

            y, _tok = allreduce(v, op, comm=intra)
            return y

        fn = jax.jit(
            jax.shard_map(
                local, mesh=intra.mesh, in_specs=spec, out_specs=spec
            )
        )
        _slice_reducers[key] = fn
    return fn


def two_tier_allreduce(x, op, intra, inter, *, token=None):
    """World allreduce over a two-fabric topology whose slices are
    SEPARATE jax runtimes: the ``intra`` MeshComm reduces this host's
    chips over ICI, the ``inter`` ProcComm reduces the per-slice
    partials across hosts over the C++ DCN bridge (TCP), and the world
    result comes back replicated across the local mesh.

    On a single multi-slice jax job, a plain :func:`world_comm`
    allreduce does all of this in one XLA collective (XLA itself routes
    ICI vs DCN — :func:`slice_comms` exposes the split).  This helper
    is the explicit composition for the launcher's process model, where
    each "slice" is its own jax world glued to the others only by the
    proc bridge — the reference's cross-node MPI tier (SURVEY §5.8).
    Exercised across two real processes by
    tests/proc/test_cross_slice.py.

    Args:
      x: global array sharded over ``intra``'s mesh axes (leading dim).
      op: reduction op (e.g. ``SUM``).
      intra: MeshComm over this process's devices (the ICI tier).
      inter: ProcComm over the launcher job's processes (the DCN tier).

    Returns ``(world, token)`` — ``world`` shaped like ``x``, every
    element holding the across-all-slices reduction.
    """
    import jax.numpy as jnp

    from mpi4jax_tpu.ops._core import as_token
    from mpi4jax_tpu.ops.allreduce import allreduce

    token = as_token(token)

    n_shards = intra.size
    if x.shape[0] % n_shards:
        raise ValueError(
            f"two_tier_allreduce: x.shape[0]={x.shape[0]} must be divisible "
            f"by the intra communicator's size ({n_shards}) — the leading "
            "dim is sharded over the intra mesh axes"
        )
    slice_red = _slice_reducer(intra, op)(x)
    # after the intra allreduce every shard position along dim 0 holds the
    # SAME reduced block of shape (x.shape[0] // n_shards, ...); stage one
    # full block (not just row 0 — shards may hold several rows) to the
    # host for the DCN hop (the proc tier's wire is host-side anyway, and
    # an eager multi-device-committed operand would otherwise drag the
    # side-effecting FFI call through the SPMD partitioner)
    import numpy as np

    block = x.shape[0] // n_shards
    partial = np.asarray(jax.device_get(slice_red[:block]))
    world, token = allreduce(partial, op, comm=inter, token=token)
    return jnp.tile(jnp.asarray(world), (n_shards,) + (1,) * (x.ndim - 1)), token
